"""Runtime loader for converted pretrained backbone weights.

The reference downloads torchvision ImageNet weights at model construction,
on rank 0 only, with no broadcast (resnet_encoder.py:56-60 — a SURVEY.md §2.4
deadlock hazard). Here pretrained weights are an offline artifact: run
tools/convert_resnet.py once (anywhere torch + the checkpoint live) to get an
.npz, point `model.pretrained_backbone_path` at it, and every process loads
identical weights before compilation — no egress, no rank asymmetry, no torch
at runtime.

The .npz key format is `<collection>/backbone/<module path>/<param>` (e.g.
`params/backbone/Bottleneck_3/Conv_1/kernel`,
`batch_stats/backbone/SyncBatchNorm_0/BatchNorm_0/mean`), exactly the flax
variable tree paths of mine_tpu.models.encoder.ResNetEncoder.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np
from flax import traverse_util

_COLLECTIONS = ("params", "batch_stats")


def load_backbone_npz(path: str) -> dict[str, dict[str, np.ndarray]]:
    """Read a converted .npz into {collection: {flat/backbone/path: array}}."""
    raw = np.load(path)
    out: dict[str, dict[str, np.ndarray]] = {c: {} for c in _COLLECTIONS}
    for key in raw.files:
        coll, sep, rest = key.partition("/")
        if not sep or coll not in _COLLECTIONS or not rest.startswith("backbone/"):
            raise ValueError(
                f"{path}: unexpected key {key!r} — not a "
                "tools/convert_resnet.py artifact?"
            )
        out[coll][rest[len("backbone/"):]] = raw[key]
    return out


def apply_pretrained_backbone(variables: dict[str, Any], path: str) -> dict[str, Any]:
    """Return `variables` with the backbone subtree replaced by the converted
    weights at `path`. Strict: the .npz must cover the backbone's parameter
    tree exactly (no missing, no extra, no shape drift) — the reference's
    tolerant strict=False load (utils.py:64-67) silently skips mismatches,
    which is how weight-layout bugs hide.
    """
    loaded = load_backbone_npz(path)
    out = dict(variables)
    for coll in _COLLECTIONS:
        tree = variables.get(coll)
        if tree is None or "backbone" not in tree:
            raise ValueError(f"model variables have no {coll}/backbone subtree")
        flat = traverse_util.flatten_dict(tree["backbone"], sep="/")
        src = loaded[coll]
        missing = sorted(set(flat) - set(src))
        extra = sorted(set(src) - set(flat))
        if missing or extra:
            raise ValueError(
                f"{path} does not match the backbone {coll} tree "
                f"(missing {len(missing)}: {missing[:4]}...; "
                f"extra {len(extra)}: {extra[:4]}...) — was it converted with "
                "the right --num-layers?"
            )
        bad_shapes = [
            (k, src[k].shape, tuple(flat[k].shape))
            for k in flat
            if tuple(src[k].shape) != tuple(flat[k].shape)
        ]
        if bad_shapes:
            raise ValueError(f"{path}: shape mismatches {bad_shapes[:4]}...")
        new_flat = {k: jnp.asarray(src[k], flat[k].dtype) for k in flat}
        new_tree = dict(tree)
        new_tree["backbone"] = traverse_util.unflatten_dict(new_flat, sep="/")
        out[coll] = new_tree
    return out
