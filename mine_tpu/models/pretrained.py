"""Runtime loader for converted pretrained weights (.npz artifacts).

The reference downloads torchvision ImageNet weights at model construction,
on rank 0 only, with no broadcast (resnet_encoder.py:56-60 — a SURVEY.md §2.4
deadlock hazard), and restores released MINE checkpoints with a tolerant
strict=False load (utils.py:40-67) that silently skips layout mismatches.
Here pretrained weights are an offline artifact: run tools/convert_resnet.py
(ImageNet backbone) or tools/convert_mine_checkpoint.py (full backbone +
decoder checkpoint) once, wherever torch and the .pth live, and every process
loads the identical .npz before compilation — no egress, no rank asymmetry,
no torch at runtime, and a STRICT key/shape check so weight-layout bugs fail
loudly instead of hiding.

The .npz key format is `<collection>/<subtree>/<module path>/<param>` (e.g.
`params/backbone/Bottleneck_3/Conv_1/kernel`,
`batch_stats/decoder/upconv_4_0/SyncBatchNorm_0/BatchNorm_0/mean`), exactly
the flax variable tree paths of mine_tpu.models.MPINetwork.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
import numpy as np
from flax import traverse_util

_COLLECTIONS = ("params", "batch_stats")
_SUBTREES = ("backbone", "decoder")


def load_npz_variables(path: str) -> dict[str, dict[str, dict[str, np.ndarray]]]:
    """Read a converted .npz into {collection: {subtree: {flat path: arr}}}."""
    raw = np.load(path)
    out: dict[str, dict[str, dict[str, np.ndarray]]] = {}
    for key in raw.files:
        parts = key.split("/", 2)
        if len(parts) != 3 or parts[0] not in _COLLECTIONS or parts[1] not in _SUBTREES:
            raise ValueError(
                f"{path}: unexpected key {key!r} — not a tools/convert_*.py "
                "artifact?"
            )
        coll, subtree, rest = parts
        out.setdefault(coll, {}).setdefault(subtree, {})[rest] = raw[key]
    return out


def apply_pretrained_npz(
    variables: dict[str, Any],
    path: str,
    expect_subtrees: Sequence[str] | None = None,
) -> dict[str, Any]:
    """Return `variables` with every subtree the .npz covers replaced by the
    converted weights. Strict: for each covered subtree the .npz must match
    the model's parameter tree exactly (no missing, no extra, no shape drift).

    expect_subtrees: when given, the .npz must cover exactly these subtrees —
    e.g. ("backbone",) for `model.pretrained_backbone_path`, so pointing it at
    a full-checkpoint artifact (which would silently replace the decoder too)
    is an error rather than a surprise.
    """
    loaded = load_npz_variables(path)
    covered = sorted({s for colls in loaded.values() for s in colls})
    if expect_subtrees is not None and covered != sorted(expect_subtrees):
        raise ValueError(
            f"{path} covers subtrees {covered}, expected "
            f"{sorted(expect_subtrees)} — wrong converter artifact for this "
            "config key?"
        )
    out = dict(variables)
    for coll in _COLLECTIONS:
        tree = variables.get(coll)
        new_tree = dict(tree) if tree is not None else {}
        for subtree in covered:
            src = loaded.get(coll, {}).get(subtree)
            if src is None:
                raise ValueError(f"{path} has no {coll}/{subtree} arrays")
            if tree is None or subtree not in tree:
                raise ValueError(
                    f"model variables have no {coll}/{subtree} subtree"
                )
            flat = traverse_util.flatten_dict(tree[subtree], sep="/")
            missing = sorted(set(flat) - set(src))
            extra = sorted(set(src) - set(flat))
            if missing or extra:
                raise ValueError(
                    f"{path} does not match the {subtree} {coll} tree "
                    f"(missing {len(missing)}: {missing[:4]}...; "
                    f"extra {len(extra)}: {extra[:4]}...) — was it converted "
                    "with the right --num-layers?"
                )
            bad_shapes = [
                (k, src[k].shape, tuple(flat[k].shape))
                for k in flat
                if tuple(src[k].shape) != tuple(flat[k].shape)
            ]
            if bad_shapes:
                raise ValueError(f"{path}: shape mismatches {bad_shapes[:4]}...")
            new_flat = {k: jnp.asarray(src[k], flat[k].dtype) for k in flat}
            new_tree[subtree] = traverse_util.unflatten_dict(new_flat, sep="/")
        out[coll] = new_tree
    return out


def apply_pretrained_backbone(variables: dict[str, Any], path: str) -> dict[str, Any]:
    """Backbone-only replacement from a tools/convert_resnet.py artifact."""
    return apply_pretrained_npz(variables, path, expect_subtrees=("backbone",))
