"""NeRF positional encoding for scalar plane disparities.

Reference: utils.py:147-196 (Embedder / get_embedder). The reference builds a
list of closures at init; here the whole embedding is one vectorized op —
frequencies are a compile-time constant folded into the jit.

Output layout matches the reference's embed-fn ordering exactly:
[x, sin(f0 x), cos(f0 x), sin(f1 x), cos(f1 x), ...] with
f_k = 2**k for log-sampled frequencies (multires 10 -> out_dim 21 for 1-D in).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array


def embed_dim(multires: int, input_dims: int = 1, include_input: bool = True) -> int:
    """Output dimension of `positional_encode` (utils.py:156-172)."""
    d = input_dims if include_input else 0
    return d + 2 * multires * input_dims


def positional_encode(x: Array, multires: int, include_input: bool = True) -> Array:
    """Encode (..., D) inputs to (..., embed_dim) features.

    Log-sampled frequency bands 2**linspace(0, multires-1, multires)
    (utils.py:164-165), interleaved sin/cos per frequency (utils.py:169-172).
    """
    freqs = 2.0 ** jnp.arange(multires, dtype=x.dtype)  # (F,)
    # (..., F, D): angle per frequency per input dim
    ang = x[..., None, :] * freqs[:, None]
    # interleave sin/cos along a new axis then flatten to (..., 2*F*D)
    sc = jnp.stack([jnp.sin(ang), jnp.cos(ang)], axis=-2)  # (..., F, 2, D)
    sc = sc.reshape(*x.shape[:-1], -1)
    if include_input:
        return jnp.concatenate([x, sc], axis=-1)
    return sc
