"""Shared normalization layers."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
from jax import Array


class SyncBatchNorm(nn.Module):
    """BN matching torch defaults (momentum 0.1 -> flax 0.9, eps 1e-5) with
    optional cross-replica stat reduction over `axis_name`.

    The reference reaches the same semantics by wrapping modules in torch
    SyncBatchNorm at the task layer (synthesis_task.py:107-115); here it is a
    property of the module. The axis_name is only applied in training — eval
    uses running averages and must not emit collectives.
    """

    axis_name: str | tuple[str, ...] | None = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        return nn.BatchNorm(
            use_running_average=not train,
            momentum=0.9,
            epsilon=1.0e-5,
            dtype=self.dtype,
            axis_name=self.axis_name if train else None,
        )(x)
