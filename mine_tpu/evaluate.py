"""Standalone evaluation CLI: metric pass of a checkpoint over the val set.

    python -m mine_tpu.evaluate --checkpoint workspace/llff_run \
        [--extra_config '{"data.training_set_path": "..."}']

The reference can only evaluate inside a training run (run_eval fires at
eval intervals on rank 0, synthesis_task.py:496-527, :660-663); here the same
jitted eval graph (full loss suite + PSNR/SSIM/LPIPS) runs against any
workspace's newest checkpoint, on the whole mesh. Config comes from the
params.yaml paired with the checkpoint, with --extra_config overrides (e.g.
a different val path).
"""

from __future__ import annotations

import argparse
import json


def main(argv: list[str] | None = None) -> dict[str, float]:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--checkpoint", required=True,
        help="training workspace dir (params.yaml + checkpoints/)",
    )
    parser.add_argument(
        "--extra_config", default=None,
        help="JSON dict of config overrides on top of the archived params.yaml",
    )
    args = parser.parse_args(argv)

    from mine_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()

    import os

    import jax

    from mine_tpu.losses import load_lpips_params
    from mine_tpu.parallel import (
        data_replica_count,
        distribute_state,
        init_multihost,
        make_mesh,
        make_parallel_eval_step,
        model_axes,
    )
    from mine_tpu.data.registry import build_dataset
    from mine_tpu.training import build_model, init_state, make_optimizer
    from mine_tpu.training import checkpoint as ckpt
    from mine_tpu.training.loop import run_evaluation
    from mine_tpu.utils import MetricWriter, make_logger

    init_multihost()
    # resolves through local_sidecar_dir, so a remote (gs://) workspace finds
    # the params.yaml its training run archived locally
    cfg = ckpt.load_paired_config(args.checkpoint, overrides=args.extra_config)
    sidecar = ckpt.local_sidecar_dir(args.checkpoint)

    mesh = make_mesh(
        cfg.mesh.data_parallel, cfg.mesh.plane_parallel,
        cfg.mesh.fsdp_parallel,
    )
    model = build_model(cfg, **model_axes(mesh))
    tx = make_optimizer(cfg, steps_per_epoch=1)
    template = init_state(
        cfg, model, tx, jax.random.PRNGKey(0), load_pretrained=False
    )
    manager = ckpt.checkpoint_manager(args.checkpoint)
    state, step = ckpt.restore(manager, template)
    if step == 0:
        raise FileNotFoundError(
            f"no checkpoint under {args.checkpoint}/checkpoints"
        )
    # table-driven placement: replicated, FSDP param shards, or ZeRO-1
    # moments, whatever the config's rule rows resolve to on this mesh
    state = distribute_state(state, cfg, mesh)

    global_batch = cfg.data.per_gpu_batch_size * data_replica_count(mesh)
    val_ds = build_dataset(cfg, "val", global_batch)
    lpips_params = load_lpips_params(cfg.training.lpips_weights_path)
    eval_step = make_parallel_eval_step(
        cfg, model, mesh, lpips_params, state=state
    )

    logger = make_logger(sidecar)
    writer = MetricWriter(os.path.join(sidecar, "eval"))
    result = run_evaluation(
        cfg, mesh, logger, writer, eval_step, state, val_ds, step
    )
    if jax.process_index() == 0:  # one JSON line, even multi-host
        print(json.dumps(
            {"step": step, **{k: round(v, 6) for k, v in result.items()}}
        ))
    return result


if __name__ == "__main__":
    main()
