"""Deterministic fault injection at named seams (`MINE_TPU_FAULTS`).

Every behavior the resilience layer promises — sentinel skip/rollback,
preemption-safe save/resume, loader retry, breaker trip/recovery — must be
provable on CPU without real hardware faults. This module is the one
injection mechanism all of them share: the production code calls a seam
(`maybe_raise("loader_raise")`, `should("nan_loss", at=step)`) that is a
single `is None` check when no schedule is installed, and the tests / the
chaos drill (tools/chaos_drill.py) install a schedule that fires each fault
exactly once at a deterministic point.

Grammar (comma-separated, whitespace-free):

    MINE_TPU_FAULTS = fault ("," fault)*
    fault           = kind "@" counter "=" int

e.g. ``nan_loss@step=7,loader_raise@batch=3,engine_raise@render=2,
sigterm@step=11``. The counter name is part of the grammar so a spec reads
as a sentence; it must match the kind's canonical counter (below) — a
mismatch is a parse error, not a silently dead fault.

Kinds and their seams:

  nan_loss@step=N      training/loop.py poisons step N's batch with NaNs
                       (the fault flows through the real loss/grad graph).
  spike_loss@step=N    resilience/sentinel.py inflates the observed host
                       loss at step N (observation-level: a genuine spike
                       cannot be induced deterministically from data).
  sigterm@step=N       training/loop.py SIGTERMs its own process after
                       completing step N (preemption).
  sigusr2@step=N       same, SIGUSR2 (out-of-band save-and-continue).
  preempt_exit@step=N  training/loop.py raises PreemptedError after step N:
                       the in-process stand-in for a preemption that the
                       emergency-checkpoint path must absorb (tier-1 tests
                       cannot let a real SIGTERM kill the test runner).
  loader_raise@batch=N data/pipeline.py raises a transient ChaosFault on
                       the Nth produced batch (proves the bounded retry).
  engine_raise@render=N  serving/engine.py raises on the Nth render
                       dispatch (proves breaker trip + 500-not-hang).
  predict_raise@predict=N  serving/engine.py raises on the Nth predict.
  corrupt_swap@swap=N  serving/server.py's hot-swap worker raises while
                       loading the Nth swap's checkpoint (the in-process
                       stand-in for a corrupt/truncated checkpoint file;
                       proves rejected-swap rollback: old generation keeps
                       serving, named error + counter, no 5xx).
  corrupt_ckpt@swap=N  serving/server.py's hot-swap worker surfaces
                       CheckpointCorrupt on the Nth swap's integrity
                       verification (the in-process stand-in for a
                       checkpoint whose sha256-of-manifest sidecar no
                       longer matches its bytes — training/checkpoint.py
                       verify_checkpoint_integrity); proves the NAMED
                       corrupt-rejection path: swap refused with
                       reason=corrupt, old generation keeps serving.
  overload_spike@request=N  serving/server.py injects synthetic overload
                       into the brownout degradation controller on its
                       Nth handled request (serving/degrade.py inject):
                       the next ticks classify as breach whatever the
                       real signals say, so the drill proves the full
                       ladder climb, per-level announcement, and the
                       one-step-at-a-time recovery deterministically.
  replica_kill@request=N  serving/server.py kills THIS replica's HTTP
                       server on its Nth handled request: the listener
                       closes and the triggering connection drops with no
                       response — exactly what a fleet router sees when a
                       replica dies mid-flood (proves failover + ring
                       convergence, tools/chaos_drill.py fleet half).
  host_kill@step=N     training/loop.py SIGKILLs its own process after
                       completing step N — a host dying mid-run. No flight
                       dump, no preemption save, nothing: the evidence and
                       the bounded exit must come from the SURVIVORS
                       (resilience/multihost.py cross-host watchdog). Set
                       only in the victim host's environment
                       (tools/multihost_harness.py per-host fault specs).
  host_stall@step=N    training/loop.py wedges THIS host after step N (an
                       infinite sleep standing in for a hung collective /
                       dead ICI link). Peers block at the next collective;
                       every host's cross-host watchdog — including the
                       stalled one's own — must dump and abort within the
                       heartbeat window instead of hanging forever.
  coord_down@init=N    resilience/multihost.py raises on the Nth bring-up
                       ATTEMPT (invocation-keyed): the in-process stand-in
                       for a coordinator that is not up yet when workers
                       dial in — proves the retrying bring-up's backoff
                       path deterministically.
  join_stall@scale=N   serving/autoscale.py raises during the Nth JOIN's
                       pre-warm (after the replica spawned, BEFORE ring
                       admission): the stand-in for a joiner that wedges
                       while bulk-fetching its future arc — proves a
                       stalled join never enters the ring (the joiner is
                       retired, membership unchanged, no 5xx).
  drain_timeout@scale=N  serving/autoscale.py raises during the Nth
                       DRAIN's hot-entry handoff (the victim is already
                       shedding): the stand-in for a handoff that expires
                       its budget — proves the drain still completes
                       (victim leaves the ring and exits) with the
                       surviving owners falling back to the peer-fetch
                       wire, never 5xx.

Two trigger styles share one `should()` call: value-keyed kinds (counter
`step`) fire when the caller's `at=` equals the trigger; invocation-keyed
kinds (`batch`/`render`/`predict`) keep an internal per-kind call count and
fire when it reaches the trigger. Each configured fault fires ONCE —
retries and replays after a rollback do not re-fire it, which is exactly
the transient-fault model the recovery paths exist for.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field

ENV_VAR = "MINE_TPU_FAULTS"

# kind -> canonical counter name; value-keyed kinds use counter "step"
KINDS: dict[str, str] = {
    "nan_loss": "step",
    "spike_loss": "step",
    "sigterm": "step",
    "sigusr2": "step",
    "preempt_exit": "step",
    "loader_raise": "batch",
    "engine_raise": "render",
    "predict_raise": "predict",
    "corrupt_swap": "swap",
    "corrupt_ckpt": "swap",
    "replica_kill": "request",
    "overload_spike": "request",
    "host_kill": "step",
    "host_stall": "step",
    "coord_down": "init",
    "join_stall": "scale",
    "drain_timeout": "scale",
}
_VALUE_KEYED = frozenset(k for k, c in KINDS.items() if c == "step")


class ChaosFault(RuntimeError):
    """The injected fault. Transient by construction (fires once), so retry
    paths treat it as retryable; non-retry paths see an ordinary error."""

    def __init__(self, kind: str, trigger: int):
        super().__init__(
            f"injected chaos fault {kind}@{KINDS[kind]}={trigger} "
            f"({ENV_VAR} schedule)"
        )
        self.kind = kind
        self.trigger = trigger


class PreemptedError(RuntimeError):
    """In-process preemption stand-in (`preempt_exit@step=N`): unwinds the
    training loop through the emergency-checkpoint path without a signal."""


@dataclass
class _Fault:
    kind: str
    trigger: int
    fired: bool = False


@dataclass
class ChaosSchedule:
    """A parsed fault schedule. Thread-safe: seams fire from the training
    main thread, the prefetch worker, and the batcher worker."""

    spec: str
    faults: list[_Fault] = field(default_factory=list)

    def __post_init__(self):
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        for part in filter(None, self.spec.replace(" ", "").split(",")):
            try:
                kind_at, value = part.split("=", 1)
                kind, counter = kind_at.split("@", 1)
                trigger = int(value)
            except ValueError:
                raise ValueError(
                    f"bad {ENV_VAR} fault {part!r}: expected kind@counter=int"
                ) from None
            if kind not in KINDS:
                raise ValueError(
                    f"unknown {ENV_VAR} fault kind {kind!r} "
                    f"(known: {sorted(KINDS)})"
                )
            if counter != KINDS[kind]:
                raise ValueError(
                    f"{ENV_VAR} fault {kind!r} counts {KINDS[kind]!r}, "
                    f"not {counter!r}"
                )
            if trigger < 1:
                raise ValueError(f"{ENV_VAR} trigger must be >= 1: {part!r}")
            self.faults.append(_Fault(kind, trigger))

    def should(self, kind: str, at: int | None = None) -> bool:
        """True exactly once per configured (kind, trigger) match.

        Value-keyed kinds require `at` (the caller's own counter, e.g. the
        global step); invocation-keyed kinds count calls to this method.
        """
        if kind not in KINDS:
            raise ValueError(f"unknown chaos kind {kind!r}")
        with self._lock:
            if at is None:
                if kind in _VALUE_KEYED:
                    raise ValueError(f"chaos kind {kind!r} needs at=<step>")
                self._counts[kind] = at = self._counts.get(kind, 0) + 1
            for f in self.faults:
                if f.kind == kind and not f.fired and f.trigger == at:
                    f.fired = True
                    return True
        return False

    def pending(self) -> list[str]:
        """Unfired faults, for end-of-drill assertions ("did every
        configured fault actually reach its seam?")."""
        with self._lock:
            return [
                f"{f.kind}@{KINDS[f.kind]}={f.trigger}"
                for f in self.faults if not f.fired
            ]


_UNPARSED = object()
_active: ChaosSchedule | None | object = _UNPARSED
_active_lock = threading.Lock()


def active() -> ChaosSchedule | None:
    """The process-wide schedule: parsed from $MINE_TPU_FAULTS on first
    call, None when unset/empty. `install()`/`uninstall()` override (tests)."""
    global _active
    if _active is _UNPARSED:
        with _active_lock:
            if _active is _UNPARSED:
                spec = os.environ.get(ENV_VAR, "")
                _active = ChaosSchedule(spec) if spec else None
    return _active  # type: ignore[return-value]


def install(spec: str) -> ChaosSchedule:
    """Install a schedule programmatically (tests); returns it."""
    global _active
    with _active_lock:
        _active = ChaosSchedule(spec)
        return _active


def uninstall() -> None:
    """Drop any schedule; the next active() re-reads the environment."""
    global _active
    with _active_lock:
        _active = _UNPARSED


def should(kind: str, at: int | None = None) -> bool:
    """Module-level seam: False (one attribute check) with no schedule."""
    schedule = active()
    return schedule.should(kind, at) if schedule is not None else False


def maybe_raise(kind: str, at: int | None = None) -> None:
    """Raise ChaosFault when the schedule says this seam fires now."""
    schedule = active()
    if schedule is not None and schedule.should(kind, at):
        trigger = at if at is not None else schedule._counts[kind]
        raise ChaosFault(kind, trigger)
