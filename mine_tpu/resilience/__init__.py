"""Fault-tolerance layer: detect, degrade, recover.

Four pieces, wired through training, data, serving, and checkpointing:

  sentinel.py  training sentinel — per-step finiteness (in-graph update
               mask) + loss-spike detection, with skip/rollback/abort
               policies (`resilience.sentinel_policy`).
  preempt.py   SIGTERM/SIGUSR2 out-of-band atomic checkpoint save, chained
               ahead of the flight recorder's dump-then-terminate.
  breaker.py   serving circuit breaker (closed/open/half-open) behind the
               admission-controlled micro-batcher.
  chaos.py     deterministic fault injection ($MINE_TPU_FAULTS) at named
               seams — the harness the tier-1 tests and
               tools/chaos_drill.py drive, so every behavior above is
               provable on CPU.
  multihost.py multi-host survival — retrying jax.distributed bring-up,
               heartbeat exchange over a shared directory, and the
               cross-host stall watchdog that turns a dead/wedged host
               into a bounded, named abort on every survivor (proven by
               tools/multihost_harness.py + the chaos drill's multihost
               half).

Import-light on purpose: nothing here touches jax at import time (chaos
seams sit on serving/data hot paths that must stay cheap when disabled).
"""

from mine_tpu.resilience.breaker import BreakerOpen, CircuitBreaker
from mine_tpu.resilience.chaos import ChaosFault, PreemptedError
from mine_tpu.resilience.multihost import (
    EXIT_HOST_STALL,
    CrossHostWatchdog,
    HeartbeatWriter,
    HostStallAbort,
    MultihostSurvival,
)
from mine_tpu.resilience.preempt import PreemptionGuard
from mine_tpu.resilience.sentinel import (
    SentinelAbort,
    SentinelRollback,
    SentinelTrip,
    TrainingSentinel,
)

__all__ = [
    "BreakerOpen",
    "ChaosFault",
    "CircuitBreaker",
    "CrossHostWatchdog",
    "EXIT_HOST_STALL",
    "HeartbeatWriter",
    "HostStallAbort",
    "MultihostSurvival",
    "PreemptedError",
    "PreemptionGuard",
    "SentinelAbort",
    "SentinelRollback",
    "SentinelTrip",
    "TrainingSentinel",
]
