"""Circuit breaker for the serving engine.

When the device backend starts failing (dead TPU tunnel — the r1–r5
pattern — OOM loops, a poisoned executable), every queued request riding
into the engine costs a full dispatch timeout and returns a 500. The
breaker converts that failure mode into fast, honest load shedding:

  closed     normal operation; consecutive failures are counted.
  open       `failure_threshold` consecutive failures tripped it: requests
             are rejected immediately (HTTP 503 + Retry-After) without
             touching the engine, /healthz reports degraded.
  half-open  after `reset_after_s` the next `allow()` admits exactly ONE
             trial request; its success closes the breaker, its failure
             re-opens it (timer restarts).

The recovery window is JITTERED (`reset_jitter`, a seeded +-fraction
drawn per trip): a fleet-wide event — a bad checkpoint push, a shared
backend hiccup — trips every replica's breaker at the same instant, and
without jitter every replica would run its half-open trial in lockstep,
stampeding the still-recovering dependency and re-tripping together.
Seeded (`jitter_seed`, distinct per replica) so the spread is
deterministic under test yet distinct across the fleet.

Thread-safe; time is injectable for deterministic tests. State changes are
reported through `on_state` (a gauge hook: 0 closed, 1 half-open, 2 open)
and trips through `on_trip` (a counter hook).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class BreakerOpen(RuntimeError):
    """Rejected because the breaker is open (maps to HTTP 503)."""

    def __init__(self, retry_after_s: float):
        super().__init__(
            f"circuit breaker open; retry after {retry_after_s:.1f}s"
        )
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 5,
        reset_after_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        on_state: Callable[[int], None] | None = None,
        on_trip: Callable[[], None] | None = None,
        reset_jitter: float = 0.0,
        jitter_seed: int | None = None,
    ):
        if failure_threshold < 0:
            raise ValueError(f"failure_threshold must be >= 0, got "
                             f"{failure_threshold}")
        if not 0.0 <= reset_jitter < 1.0:
            raise ValueError(
                f"reset_jitter must be in [0, 1), got {reset_jitter}"
            )
        # threshold 0 disables the breaker entirely (allow() is always True)
        self.failure_threshold = int(failure_threshold)
        self.reset_after_s = float(reset_after_s)
        self.reset_jitter = float(reset_jitter)
        self._jitter_rng = random.Random(
            0 if jitter_seed is None else jitter_seed
        )
        # the window actually in force for the CURRENT open period;
        # re-drawn at every trip (guarded-by: self._lock)
        self._effective_reset_s = self.reset_after_s
        self._clock = clock
        self._on_state = on_state
        self._on_trip = on_trip
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._trial_inflight = False
        self.trips = 0
        if on_state is not None:
            on_state(STATE_CODES[CLOSED])

    # -- state ----------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _set_state_locked(self, state: str) -> None:
        self._state = state
        if self._on_state is not None:
            self._on_state(STATE_CODES[state])

    def _maybe_half_open_locked(self) -> None:
        if (self._state == OPEN
                and self._clock() - self._opened_at
                >= self._effective_reset_s):
            self._set_state_locked(HALF_OPEN)
            self._trial_inflight = False

    def retry_after_s(self) -> float:
        """Seconds until the breaker half-opens (0 when not open)."""
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(
                0.0,
                self._effective_reset_s - (self._clock() - self._opened_at),
            )

    # -- admission ------------------------------------------------------------

    def rejecting(self) -> bool:
        """Pure admission probe: True while open (before the reset timer).
        Does NOT consume the half-open trial slot — use at enqueue time so
        the trial is spent by the dispatch-time `allow()`, not by admission.
        """
        with self._lock:
            self._maybe_half_open_locked()
            return self._state == OPEN

    def allow(self) -> bool:
        """Dispatch-time gate. In half-open state admits exactly one trial
        at a time; the trial's record_success/record_failure decides."""
        if self.failure_threshold == 0:
            return True
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._trial_inflight:
                self._trial_inflight = True
                return True
            return False

    # -- outcomes -------------------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._trial_inflight = False
            if self._state != CLOSED:
                self._set_state_locked(CLOSED)

    def record_failure(self) -> None:
        if self.failure_threshold == 0:
            return
        with self._lock:
            self._consecutive_failures += 1
            self._trial_inflight = False
            should_trip = (
                self._state == HALF_OPEN
                or (self._state == CLOSED
                    and self._consecutive_failures >= self.failure_threshold)
            )
            if should_trip:
                self._opened_at = self._clock()
                # draw this open period's recovery window: replicas
                # sharing a trip instant still re-probe at distinct ones
                self._effective_reset_s = self.reset_after_s * (
                    1.0 + self.reset_jitter
                    * self._jitter_rng.uniform(-1.0, 1.0)
                )
                if self._state != OPEN:
                    self.trips += 1
                    if self._on_trip is not None:
                        self._on_trip()
                self._set_state_locked(OPEN)
