"""Multi-host training survival: retrying bring-up, heartbeat exchange,
and the cross-host stall watchdog.

The dominant pod-scale failure mode is not a NaN — it is a HOST dying or
wedging mid-step. Every collective then blocks on the missing peer, and
without this module the job hangs silently until a human notices (the
ROADMAP's "unwitnessed rendezvous hang": long-horizon MPI training loses a
pod hour per incident). Three pieces close that hole, all CPU-provable via
tools/multihost_harness.py (N subprocesses on one box running the SAME
jax.distributed code path a pod runs):

  bring_up()            init_multihost with bounded retry + backoff for
                        FAST failures (coordinator not accepting yet —
                        workers routinely dial in before the coordinator
                        binds). A bring-up TIMEOUT stays terminal: the
                        stuck rendezvous thread cannot be torn down
                        in-process (parallel/mesh.py), so the honest move
                        is to die named and let the scheduler reschedule.
                        Chaos seam: `coord_down@init=N` fires on the Nth
                        attempt (resilience/chaos.py).

  HeartbeatWriter       one JSON file per host under a shared directory
                        (`host_<i>.json`: step, wall ts, host data bytes,
                        done flag), atomically replaced at each
                        log-interval sync — piggybacked on the host fetch
                        the loop already does, so it costs one tiny write
                        per interval and nothing per step.

  CrossHostWatchdog     a daemon thread polling EVERY heartbeat file
                        (peers and its own). Any file stale past
                        `window_s` means some host stopped making progress
                        — killed (its file freezes) or stuck in a
                        collective (every blocked host's file freezes,
                        including the watcher's own, which is exactly why
                        the watcher judges its own file too: a host
                        wedged in-collective self-detects). Verdict:
                        flight dump (reason `host_stall`, stale peers +
                        ages in meta), an abort marker JSON next to the
                        heartbeats, then `os._exit(EXIT_HOST_STALL)` — a
                        clean NAMED abort within a bounded window instead
                        of an indefinite NCCL/ICI hang. A host that
                        finished `fit()` marks itself done and is never
                        judged stale.

Clock discipline: staleness compares each file's recorded wall-clock
`ts` against local `time.time()` — hosts of one box share a clock; pods
must keep NTP skew well under the window (document the window >= 2x the
slowest legitimate heartbeat gap PLUS skew). The heartbeat directory must
be storage every host can read (single box: the workspace sidecar; pod:
NFS — a gs:// workspace cannot carry plain-file heartbeats, see
resilience.multihost_heartbeat_dir).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable

from mine_tpu.resilience import chaos

# the named abort's exit code: distinct from signal deaths (negative), 0/1
# success/failure, and orbax/JAX crashes — the harness (and any pod
# supervisor) can tell "watchdog abort" from "crash" by this alone
EXIT_HOST_STALL = 83

# the startup beat's staleness allowance: steady-state beats only begin at
# the first completed log interval, so without an initial beat a host
# killed DURING the minutes-long first compile would leave nothing for
# peers' watchdogs to judge (they would hang until jax's own ~100s
# coordination SIGABRT — bounded, but evidence-less and unnamed). Every
# host writes one beat at watchdog start carrying this allowance: wide
# enough for any first compile, narrow enough that a compile-phase death
# still ends in the NAMED abort.
STARTUP_ALLOWANCE_S = 600.0

# start()-time cleanup only removes PREVIOUS runs' heartbeat/marker files;
# fresh files are this run's peers racing us to start (their startup
# beats must survive process 0's sweep). Peers reach start() within a few
# seconds of each other — it sits right after the bring-up rendezvous
# they all exited together — while a dead run's files are at least a
# restart-latency old. A restart launched within this many seconds of a
# crash can leave the dead run's beats standing and false-trip the
# watchdog once the grace expires; wait out the margin (or clear the
# heartbeat dir) before hot-relaunching a just-crashed workspace.
_CLEANUP_MIN_AGE_S = 10.0

_MARKER_PREFIX = "multihost_abort_p"


def named_abort(
    directory: str,
    process_index: int,
    reason: str,
    detail: dict | None = None,
    flight: Any = None,
    logger: Any = None,
    exit_fn: Callable[[int], None] = os._exit,
    linger_s: float = 0.0,
) -> None:
    """THE bounded named exit: abort marker -> flight dump -> (linger) ->
    exit_fn(EXIT_HOST_STALL). Shared by the cross-host watchdog (reason
    `host_stall`), the marker broadcast (`peer_abort`), and the teardown
    failsafe (`teardown_hang`). Every step is best-effort — a
    half-written dump beats an abort helper that dies before exiting.

    The MARKER goes first and the exit waits `linger_s`: the first host
    to exit takes the in-process jax coordination service down with it
    (when it is host 0), and the runtime then SIGABRTs any peer that has
    not exited yet — the marker broadcast plus the linger gives every
    peer's watchdog one poll to see the marker and take ITS OWN named
    exit with evidence, instead of an evidence-less -SIGABRT."""
    detail = dict(detail or {}, process_index=process_index)
    try:
        marker = os.path.join(
            directory, f"{_MARKER_PREFIX}{process_index}.json"
        )
        tmp = f"{marker}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(dict(detail, reason=reason,
                           exit_code=EXIT_HOST_STALL), fh)
        os.replace(tmp, marker)
    except OSError:
        pass
    if logger is not None:
        try:
            logger.error(
                "multihost named abort (%s): %s — exit code %d",
                reason, detail, EXIT_HOST_STALL,
            )
        except Exception:  # noqa: BLE001
            pass
    if flight is not None:
        try:
            flight.dump(reason, extra=detail)
        except Exception:  # noqa: BLE001
            pass
    if linger_s > 0:
        time.sleep(linger_s)
    exit_fn(EXIT_HOST_STALL)


class HostStallAbort(RuntimeError):
    """A peer host went silent past the watchdog window. Raised by the
    synchronous `check()` API; the watchdog THREAD never raises (nothing
    would catch it) — it dumps, writes the marker, and exits the process
    with EXIT_HOST_STALL."""

    def __init__(self, stale: dict[int, float], window_s: float):
        peers = ", ".join(
            f"host {i} silent {age:.1f}s" for i, age in sorted(stale.items())
        )
        super().__init__(
            f"cross-host watchdog: {peers} (window {window_s:.1f}s) — a "
            "host died or wedged in a collective; aborting instead of "
            "hanging"
        )
        self.stale = stale
        self.window_s = window_s


# ----------------------------------------------------------- bring-up retry


def bring_up(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    attempts: int = 3,
    backoff_s: float = 2.0,
    timeout_s: float | None = None,
    initialize_fn: Any = None,
    logger: Any = None,
    sleep_fn: Callable[[float], None] = time.sleep,
) -> None:
    """init_multihost with bounded retry for fast bring-up failures.

    Retryable: ConnectionError/OSError from a coordinator that is not
    accepting yet, and the `coord_down` chaos seam (invocation-keyed on
    the attempt). NOT retryable: MultihostInitTimeout — the timed-out
    rendezvous thread is still blocked inside jax.distributed and a second
    initialize would either join it or report already-initialized while
    nothing actually rendezvoused; the process must be rescheduled — and
    any other error (a real config problem retried 3x is 3x the noise).
    No-op exactly when init_multihost is a no-op (single-host runs)."""
    from mine_tpu.parallel.mesh import init_multihost

    if logger is None:
        # bring-up runs before the workspace logger exists; the default
        # logging lastResort handler still puts WARNINGs on stderr, which
        # is exactly where a launcher looks
        logger = logging.getLogger("mine_tpu")
    last: BaseException | None = None
    for attempt in range(1, max(attempts, 1) + 1):
        try:
            chaos.maybe_raise("coord_down")
            init_multihost(
                coordinator=coordinator,
                timeout_s=timeout_s,
                initialize_fn=initialize_fn,
                num_processes=num_processes,
                process_id=process_id,
            )
            return
        except (OSError, chaos.ChaosFault) as exc:
            # OSError covers the whole fast-failure class — connection
            # refused AND a coordinator hostname not resolvable yet
            # (socket.gaierror); MultihostInitTimeout is a RuntimeError,
            # so the terminal-timeout rule is untouched
            last = exc
            if attempt >= max(attempts, 1):
                raise
            delay = backoff_s * (2.0 ** (attempt - 1))
            if logger is not None:
                logger.warning(
                    "multi-host bring-up attempt %d/%d failed (%s: %s); "
                    "retrying in %.1fs",
                    attempt, attempts, type(exc).__name__, exc, delay,
                )
            sleep_fn(delay)
    raise last  # pragma: no cover - loop always returns or raises


# ------------------------------------------------------- heartbeat exchange

# THE heartbeat file schema. Required keys appear in every beat; optional
# keys only when the writer had the value. Three independent consumers
# read these files — the cross-host watchdog, the straggler table below,
# and the trace collector (obs/collect.py training_timeline) — so the
# contract is pinned by a tier-1 test (tests/test_multihost.py): a writer
# or reader drifting from it fails with the key named, not with a
# silently-wrong verdict.
BEAT_REQUIRED_KEYS = frozenset(
    {"process_index", "pid", "ts", "step", "data_bytes", "done"}
)
BEAT_OPTIONAL_KEYS = frozenset({"allowance_s", "sync_wait_ms"})


def beat_path(directory: str, process_index: int) -> str:
    return os.path.join(directory, f"host_{process_index}.json")


def read_beat(path: str) -> dict | None:
    """The beat, or None for missing/garbled files (a half-written beat is
    impossible — writes are atomic renames — but a peer may not have
    beaten yet, and evidence reading must never raise)."""
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


class HeartbeatWriter:
    """Atomically-replaced per-host heartbeat file. One instance per
    process; `beat()` is called from the training loop's log-interval
    block (it already syncs host-side there, so the write piggybacks on an
    existing pause, never on the step hot path)."""

    def __init__(self, directory: str, process_index: int,
                 now_fn: Callable[[], float] = time.time):
        self.directory = directory
        self.process_index = int(process_index)
        self._now = now_fn
        os.makedirs(directory, exist_ok=True)

    def beat(self, step: int | None = None, data_bytes: int | None = None,
             done: bool = False, allowance_s: float | None = None,
             sync_wait_ms: float | None = None) -> None:
        """`allowance_s` widens THIS beat's staleness window beyond the
        watchdog's (the startup beat carries the compile-sized allowance:
        a host killed during the minutes-long first compile is still
        detected — just on the startup clock, not the steady-state one).
        `sync_wait_ms` is the host's last log-interval device sync wall
        time — the collectives block until the SLOWEST host, so a host
        with a LOW sync wait next to peers with high ones is itself the
        straggler everyone else is waiting for (straggler_table)."""
        record = {
            "process_index": self.process_index,
            "pid": os.getpid(),
            "ts": self._now(),
            "step": step,
            # host-materialized loader bytes: the per-host data-sharding
            # measurement rides the heartbeat so the harness can assert
            # each host loaded 1/N of the global batch without scraping
            # per-process /metrics endpoints
            "data_bytes": data_bytes,
            "done": bool(done),
        }
        if allowance_s is not None:
            record["allowance_s"] = float(allowance_s)
        if sync_wait_ms is not None:
            record["sync_wait_ms"] = round(float(sync_wait_ms), 3)
        path = beat_path(self.directory, self.process_index)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                json.dump(record, fh)
            os.replace(tmp, path)  # readers see old or new, never half
        except OSError:
            # heartbeating is evidence, not correctness: a full disk must
            # not kill training (the watchdog on peers will judge us stale
            # — which, with a dead evidence disk, is the right verdict)
            pass


# ------------------------------------------------------ cross-host watchdog


class CrossHostWatchdog:
    """Judge every host's heartbeat file; abort boundedly on staleness.

    A file is judged only once it EXISTS: hosts write no beat until their
    first completed log interval, so the (minutes-long) initial compile
    can never false-trip the window — and after the first beats land, all
    hosts are in lockstep at collectives, so beats stay aligned. `done`
    beats are exempt (normal completion is not a stall).

    `check()` is the synchronous core (unit-testable with an injected
    clock); `start()` wraps it in a poll thread whose verdict is: flight
    dump -> abort marker -> exit_fn(EXIT_HOST_STALL). The marker
    (`multihost_abort_p<i>.json` next to the heartbeats) is what the
    harness — and an operator — reads for the named diagnosis; the exit
    code is what a supervisor reacts to."""

    def __init__(
        self,
        directory: str,
        process_index: int,
        window_s: float,
        poll_s: float | None = None,
        grace_s: float | None = None,
        flight: Any = None,
        logger: Any = None,
        now_fn: Callable[[], float] = time.time,
        exit_fn: Callable[[int], None] = os._exit,
    ):
        self.directory = directory
        self.process_index = int(process_index)
        self.window_s = float(window_s)
        self.poll_s = poll_s if poll_s is not None else max(
            min(self.window_s / 4.0, 1.0), 0.05
        )
        # startup grace: judgments begin one full window after start() —
        # process 0 clears the PREVIOUS run's heartbeat files at its own
        # start (an elastic restart at fewer hosts would otherwise judge
        # the dead 4th host's leftover file instantly), and peers' first
        # polls must not race that cleanup
        self.grace_s = float(grace_s) if grace_s is not None else self.window_s
        self.flight = flight
        self.logger = logger
        self._now = now_fn
        self._exit = exit_fn
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def check(self) -> dict[int, float]:
        """{process_index: staleness seconds} for every live (not-done)
        heartbeat file older than the window. Empty dict = healthy."""
        stale: dict[int, float] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return stale
        now = self._now()
        for name in names:
            if not (name.startswith("host_") and name.endswith(".json")):
                continue
            beat = read_beat(os.path.join(self.directory, name))
            if beat is None or beat.get("done"):
                continue
            age = now - float(beat.get("ts", 0.0))
            # a beat may carry its own (wider) allowance — the startup
            # beat's compile-sized window (HeartbeatWriter.beat)
            window = max(self.window_s, float(beat.get("allowance_s", 0.0)))
            if age > window:
                stale[int(beat.get("process_index", -1))] = age
        return stale

    def check_or_raise(self) -> None:
        stale = self.check()
        if stale:
            raise HostStallAbort(stale, self.window_s)

    # -- the poll thread ----------------------------------------------------

    def start(self) -> "CrossHostWatchdog":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._watch, name="mine-multihost-watchdog",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _watch(self) -> None:
        started = self._now()
        while not self._stop.wait(self.poll_s):
            if self._now() - started < self.grace_s:
                continue  # startup grace (see __init__)
            # marker broadcast: a peer that already took the named abort
            # is about to exit (and may take the in-process coordination
            # service with it) — join it NOW with our own evidence rather
            # than eat the runtime's evidence-less SIGABRT moments later
            peers = {
                i: m for i, m in abort_markers(self.directory).items()
                if i != self.process_index
            }
            if peers:
                named_abort(
                    self.directory, self.process_index, "peer_abort",
                    detail={"peer_markers": {
                        str(i): m.get("reason") for i, m in peers.items()
                    }},
                    flight=self.flight, logger=self.logger,
                    exit_fn=self._exit, linger_s=self._linger_s(),
                )
                return
            stale = self.check()
            if stale:
                self._abort(stale)
                return

    def _linger_s(self) -> float:
        """Only process 0 lingers — its exit takes the in-process jax
        coordination service down, and the runtime then SIGABRTs any
        still-alive peer mid-evidence (observed: a survivor killed 80 ms
        after host 0's exit, DURING its own linger). Host 0 waiting ~3
        polls lets every peer see the marker broadcast and exit first;
        other hosts' exits endanger nobody, so they leave immediately."""
        if self.process_index != 0:
            return 0.0
        return min(3.0 * self.poll_s, 5.0)

    def _abort(self, stale: dict[int, float]) -> None:
        """The bounded-exit verdict (named_abort with the stall detail)."""
        suspect = max(stale, key=stale.get)
        named_abort(
            self.directory, self.process_index, "host_stall",
            detail={
                "stale_hosts": {
                    str(i): round(a, 3) for i, a in stale.items()
                },
                # oldest silence: the host that froze first
                "suspect": suspect,
                "window_s": self.window_s,
            },
            flight=self.flight, logger=self.logger, exit_fn=self._exit,
            linger_s=self._linger_s(),
        )


def straggler_table(directory: str) -> dict:
    """Per-host progress attribution off the heartbeat files: who is the
    slowest host, and by how much — the question a wedged-but-not-dead
    host raises BEFORE the watchdog window expires and kills the run.

    Reference time is the NEWEST beat (not the wall clock), so the table
    reads identically live and post-mortem (the harness and the chaos
    drill read it after the processes exited). Per row: the host's last
    step, how many steps behind the front-runner it is, how long it has
    been silent relative to the newest beat, and its last log-interval
    sync wait (a straggler shows a LOW sync wait while every peer's is
    high — the peers are waiting for it in the collective). `suspect` is
    the worst live (not-done) host, named only when it is actually behind;
    `skew_fraction` = its deficit over the front-runner's step count."""
    beats: list[dict] = []
    try:
        names = os.listdir(directory)
    except OSError:
        names = []
    for name in names:
        if name.startswith("host_") and name.endswith(".json"):
            beat = read_beat(os.path.join(directory, name))
            if beat is not None:
                beats.append(beat)
    if not beats:
        return {"rows": [], "suspect": None, "skew_fraction": 0.0}
    ref_ts = max(float(b.get("ts", 0.0)) for b in beats)
    ref_step = max(int(b.get("step") or 0) for b in beats)
    rows = []
    for b in sorted(beats, key=lambda b: int(b.get("process_index", -1))):
        step = int(b.get("step") or 0)
        rows.append({
            "host": int(b.get("process_index", -1)),
            "step": b.get("step"),
            "behind_steps": max(ref_step - step, 0),
            "silent_s": round(
                max(ref_ts - float(b.get("ts", ref_ts)), 0.0), 3
            ),
            "sync_wait_ms": b.get("sync_wait_ms"),
            "done": bool(b.get("done")),
        })
    live = [r for r in rows if not r["done"]]
    suspect = None
    skew_fraction = 0.0
    if live:
        worst = max(live, key=lambda r: (r["behind_steps"], r["silent_s"]))
        skew_fraction = round(
            worst["behind_steps"] / max(ref_step, 1), 4
        )
        if worst["behind_steps"] > 0:
            suspect = worst["host"]
    return {"rows": rows, "suspect": suspect, "skew_fraction": skew_fraction}


def abort_markers(directory: str) -> dict[int, dict]:
    """{process_index: marker} for every abort marker under `directory` —
    the harness/operator read side of the watchdog's verdict."""
    out: dict[int, dict] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if name.startswith(_MARKER_PREFIX) and name.endswith(".json"):
            marker = read_beat(os.path.join(directory, name))
            if marker is not None:
                out[int(name[len(_MARKER_PREFIX):-len(".json")])] = marker
    return out


# ----------------------------------------------------- trainer convenience


class MultihostSurvival:
    """The Trainer-facing bundle: heartbeat writer + watchdog, created
    only when this run actually spans processes. One object so the loop's
    integration is three calls (start / beat / stop)."""

    def __init__(self, directory: str, process_index: int, window_s: float,
                 flight: Any = None, logger: Any = None,
                 exit_fn: Callable[[int], None] = os._exit):
        self.directory = directory
        self.process_index = int(process_index)
        self.window_s = float(window_s)
        self.flight = flight
        self.logger = logger
        self._exit = exit_fn
        self.writer = HeartbeatWriter(directory, process_index)
        self._failsafe: threading.Timer | None = None
        self.watchdog = None
        if window_s > 0:
            self.watchdog = CrossHostWatchdog(
                directory, process_index, window_s,
                flight=flight, logger=logger,
            )

    @classmethod
    def maybe_create(cls, cfg: Any, sidecar_dir: str, flight: Any = None,
                     logger: Any = None) -> "MultihostSurvival | None":
        """None on single-process runs — the module costs nothing there."""
        import jax

        if jax.process_count() <= 1:
            return None
        directory = cfg.resilience.multihost_heartbeat_dir or os.path.join(
            sidecar_dir, "heartbeats"
        )
        return cls(
            directory, jax.process_index(),
            cfg.resilience.multihost_watchdog_s,
            flight=flight, logger=logger,
        )

    def start(self) -> None:
        if self.process_index == 0:
            # clear the PREVIOUS run's evidence: an elastic restart at
            # fewer hosts must not judge a dead host's leftover heartbeat
            # (or re-read its abort markers as fresh). Age-gated so the
            # sweep cannot eat THIS run's peers' fresh startup beats —
            # the previous run's files are minutes old by any restart.
            now = time.time()
            try:
                for name in os.listdir(self.directory):
                    if not (name.startswith("host_") or
                            name.startswith(_MARKER_PREFIX)):
                        continue
                    path = os.path.join(self.directory, name)
                    try:
                        if now - os.path.getmtime(path) > _CLEANUP_MIN_AGE_S:
                            os.remove(path)
                    except OSError:
                        pass
            except OSError:
                pass
        # the startup beat: a host that dies before its first log-interval
        # beat (bring-up straggler, killed mid-compile) is still judged —
        # on the compile-sized allowance instead of the steady window
        self.writer.beat(allowance_s=STARTUP_ALLOWANCE_S)
        if self.watchdog is not None:
            self.watchdog.start()

    def beat(self, step: int, data_bytes: int | None = None,
             sync_wait_ms: float | None = None) -> None:
        self.writer.beat(step=step, data_bytes=data_bytes,
                         sync_wait_ms=sync_wait_ms)

    def stragglers(self) -> dict:
        """The straggler table over this run's heartbeat dir — what the
        training loop logs each interval and the drill verdict embeds."""
        return straggler_table(self.directory)

    def arm_failsafe(self, seconds: float | None = None,
                     reason: str = "teardown_hang",
                     linger_s: float | None = None) -> None:
        """Bound this process's remaining lifetime: it is on a failure
        path, and everything left to do — the emergency device_get (which
        may wait on a dead peer's collective), checkpoint drains, and
        above all jax.distributed's atexit SHUTDOWN BARRIER (observed to
        park a survivor for the coordination service's ~100s heartbeat
        timeout and then SIGABRT it) — can block on peers that will never
        answer. If the process is still alive `seconds` from now, take
        the named abort instead. Arming twice keeps the first deadline;
        a process that exits sooner never sees it (daemon timer)."""
        if self._failsafe is not None:
            return
        if seconds is None:
            seconds = self.window_s if self.window_s > 0 else 60.0
        if linger_s is None:
            # the watchdog's rule: only process 0 lingers (its exit kills
            # the in-process coordination service; see _linger_s)
            linger_s = 3.0 if self.process_index == 0 else 0.0
        self._failsafe = threading.Timer(
            seconds,
            named_abort,
            args=(self.directory, self.process_index, reason),
            kwargs={
                "detail": {"failsafe_s": seconds},
                "flight": self.flight, "logger": self.logger,
                "exit_fn": self._exit,
                # same idea as the watchdog's linger: let peers see the
                # marker before this exit can take the coordination
                # service down with it
                "linger_s": linger_s,
            },
        )
        self._failsafe.daemon = True
        self._failsafe.start()

    def stop(self, done: bool, step: int | None = None,
             data_bytes: int | None = None,
             sync_wait_ms: float | None = None) -> None:
        """`done=True` on clean fit completion ONLY: watchdog off, final
        done beat (exempts this host from peers' staleness judgment; the
        last sync wait rides along so a finished run's straggler table
        keeps the attribution column). `done=False` is a FAILING exit:
        the watchdog stays armed and the failsafe deadline arms on top —
        a crashing host must stay "silent" for peers to judge, and its
        own teardown must stay bounded (arm_failsafe)."""
        if done:
            if self.watchdog is not None:
                self.watchdog.stop()
            if self._failsafe is not None:
                self._failsafe.cancel()
            self.writer.beat(step=step, data_bytes=data_bytes, done=True,
                             sync_wait_ms=sync_wait_ms)
        else:
            self.arm_failsafe()
