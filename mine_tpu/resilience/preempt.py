"""Preemption guard: an out-of-band checkpoint save on SIGTERM/SIGUSR2.

TPU preemptions deliver SIGTERM with a short grace window; everything since
the last periodic checkpoint is lost unless the process saves NOW. The
guard installs handlers that run the caller's `save_fn` first and then
CHAIN to whatever handler was installed before it:

  * Installed after the flight recorder (obs/flight.py), the SIGTERM order
    becomes: atomic checkpoint save -> flight dump -> re-delivered SIGTERM
    with the original disposition (termination semantics unchanged — the
    save and the evidence are the only additions).
  * With no previous Python handler, SIGTERM still terminates (the default
    disposition is restored and the signal re-delivered); SIGUSR2 becomes
    save-and-continue (its default disposition — terminate — is NOT
    chained: an operator poking a live run for a checkpoint must not kill
    it).

CPython runs signal handlers on the main thread between bytecodes, so the
save interrupts the step loop at a safe host point; the device-side step in
flight is untouched (the loop's `_live_state` is the last COMPLETED step).
`save_fn` failures are logged, never raised — a broken save must not block
termination.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Any, Callable


class PreemptionGuard:
    def __init__(
        self,
        save_fn: Callable[[str], None],
        logger: Any = None,
        signals: tuple[int, ...] = (signal.SIGTERM, signal.SIGUSR2),
    ):
        self.save_fn = save_fn
        self.logger = logger
        self._signals = signals
        self._prev: dict[int, Any] = {}
        self.triggered: list[str] = []  # signal names handled, oldest first

    def install(self) -> "PreemptionGuard":
        """Install handlers (main thread only — CPython's rule); no-op off
        the main thread so library use inside tests/workers stays safe."""
        if threading.current_thread() is not threading.main_thread():
            return self
        for sig in self._signals:
            try:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            except (ValueError, OSError):  # exotic platform / nested ctx
                pass
        return self

    def uninstall(self) -> None:
        if threading.current_thread() is not threading.main_thread():
            return
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()

    def _on_signal(self, signum: int, frame: Any) -> None:
        name = signal.Signals(signum).name
        self.triggered.append(name)
        try:
            self.save_fn(f"signal_{name.lower()}")
        except BaseException:  # noqa: BLE001 - never block termination
            if self.logger is not None:
                self.logger.exception("preemption save failed (%s)", name)
        prev = self._prev.get(signum)
        if callable(prev):
            # chain (e.g. the flight recorder's dump-then-terminate)
            prev(signum, frame)
        elif signum == signal.SIGTERM:
            # no Python handler underneath: termination must still
            # terminate — restore the original disposition and re-deliver
            signal.signal(
                signum, prev if prev is not None else signal.SIG_DFL
            )
            os.kill(os.getpid(), signum)
        # SIGUSR2 with no previous handler: save-and-continue by design
