"""Training sentinel: detect a poisoned run and apply a recovery policy.

The reference trains blind: a single non-finite loss silently corrupts the
params and every step after it is wasted work (SURVEY.md §5.3 lists no
containment at all). The sentinel closes that with two detectors and three
policies (`resilience.sentinel_policy`):

Detectors
  finiteness  every train step computes `isfinite(loss) & isfinite(|grad|)`
              in-graph (training/step.py) and — for any policy other than
              "off" — MASKS the update in the same XLA program, so params
              provably never absorb a non-finite update. The per-step flag
              is a scalar the loop hands to `observe()` WITHOUT a device
              sync; flags resolve in one batched device_get at each log
              interval / checkpoint boundary, keeping steps fully async.
  spike       the host loss (already fetched each log interval) against
              `spike_factor` x the running median of the last
              `spike_window` samples, after `spike_min_history` samples.

Policies on a trip
  skip      count it and continue — the in-graph mask already dropped the
            poisoned update(s).
  rollback  raise SentinelRollback; the training loop restores the
            last-good checkpoint (training/checkpoint.py `last_good`
            pointer) and rebuilds the data iterator at that position.
            Bounded by `resilience.max_rollbacks`, then escalates to abort.
  abort     raise SentinelAbort (the emergency-checkpoint path persists the
            last completed step on the way out).

Every trip emits a flight-recorder dump (obs/flight.py) and ticks the
`mine_train_sentinel_*` counter family on the training metrics registry.
"""

from __future__ import annotations

import statistics
from collections import deque
from typing import Any

from mine_tpu.resilience import chaos

POLICIES = ("off", "skip", "rollback", "abort")


class SentinelTrip(RuntimeError):
    """Base of the raising sentinel outcomes."""


class SentinelRollback(SentinelTrip):
    """Restore last-good and re-seed the data iterator (caught by the
    training loop's rollback driver)."""


class SentinelAbort(SentinelTrip):
    """Unrecoverable by policy: stop training (emergency checkpoint runs)."""


class TrainingSentinel:
    def __init__(
        self,
        res_cfg: Any,  # ResilienceConfig
        registry: Any,  # utils.metrics.MetricsRegistry
        logger: Any,
        flight: Any | None = None,  # obs.FlightRecorder
    ):
        if res_cfg.sentinel_policy not in POLICIES:
            raise ValueError(
                f"resilience.sentinel_policy={res_cfg.sentinel_policy!r} "
                f"must be one of {POLICIES}"
            )
        self.policy = res_cfg.sentinel_policy
        self.spike_factor = float(res_cfg.sentinel_spike_factor)
        self.spike_min_history = int(res_cfg.sentinel_spike_min_history)
        self.logger = logger
        self.flight = flight
        self._pending: list[tuple[int, Any]] = []  # (step, device flag)
        # a bad vet() verdict (non-raising, signal-handler context) parks
        # here until the next check() applies the policy
        self._deferred_reason: str | None = None
        self._history: deque[float] = deque(
            maxlen=max(int(res_cfg.sentinel_spike_window), 1)
        )
        self.nonfinite_steps = registry.counter(
            "mine_train_sentinel_nonfinite_steps_total",
            "train steps whose loss or grad-norm was non-finite",
        )
        self.skipped_updates = registry.counter(
            "mine_train_sentinel_skipped_updates_total",
            "optimizer updates dropped in-graph by the finiteness mask",
        )
        self.trips = registry.counter(
            "mine_train_sentinel_trips_total",
            "sentinel trips by reason (nonfinite|spike) and action",
        )
        self.rollbacks = registry.counter(
            "mine_train_sentinel_rollbacks_total",
            "last-good checkpoint restores triggered by the sentinel",
        )

    @property
    def enabled(self) -> bool:
        return self.policy != "off"

    # -- per-step (async; no device sync) -------------------------------------

    def observe(self, step: int, skipped_flag: Any) -> None:
        """Queue one step's in-graph nonfinite/skip flag (a device scalar:
        1.0 = the update was non-finite and masked) for the next check()."""
        if self.enabled and skipped_flag is not None:
            self._pending.append((step, skipped_flag))

    # -- log-interval / checkpoint-boundary -----------------------------------

    def _resolve_flags(self) -> str | None:
        """Fetch queued flags in one device_get; tick counters; return
        "nonfinite" when any step's update was masked (never raises)."""
        if not self._pending:
            return None
        import jax

        flags = jax.device_get([flag for _, flag in self._pending])
        bad = [s for (s, _), v in zip(self._pending, flags)
               if float(v) > 0.0]
        self._pending.clear()
        if not bad:
            return None
        self.nonfinite_steps.inc(len(bad))
        self.skipped_updates.inc(len(bad))
        self.logger.warning(
            "sentinel: non-finite loss/grad at step%s %s — update%s "
            "dropped in-graph",
            "s" if len(bad) > 1 else "", bad,
            "s" if len(bad) > 1 else "",
        )
        return "nonfinite"

    def vet(self, step: int) -> bool:
        """Signal-handler-safe vetting (preemption saves): resolve pending
        flags WITHOUT raising; True = clean, safe to bless as last-good.
        A bad verdict is deferred to the next check(), so a SIGUSR2
        save-and-continue still trips the configured policy afterwards."""
        if not self.enabled:
            return True
        reason = self._resolve_flags()
        if reason is not None:
            self._deferred_reason = reason
            return False
        return self._deferred_reason is None

    def check(self, host_loss: float | None, step: int) -> None:
        """Resolve pending flags and spike-check the host loss; raises
        SentinelRollback/SentinelAbort per policy. host_loss=None is a
        flags-only flush (checkpoint boundaries, epoch ends)."""
        if not self.enabled:
            return
        reason, self._deferred_reason = self._deferred_reason, None
        reason = self._resolve_flags() or reason
        if host_loss is not None:
            import math

            if chaos.should("spike_loss", at=step):
                # observation-level injection: a deterministic genuine spike
                # cannot be induced from data alone (chaos.py docstring)
                host_loss = host_loss * max(self.spike_factor, 1.0) * 100.0
            if not math.isfinite(host_loss):
                reason = reason or "nonfinite"
            else:
                if (reason is None and self.spike_factor > 0
                        and len(self._history) >= self.spike_min_history):
                    median = statistics.median(self._history)
                    if median > 0 and host_loss > self.spike_factor * median:
                        reason = "spike"
                        self.logger.warning(
                            "sentinel: loss spike at step %d: %.4g > %.3g x "
                            "median %.4g", step, host_loss,
                            self.spike_factor, median,
                        )
                if reason is None:
                    # poisoned samples stay out of the median baseline
                    self._history.append(host_loss)
        if reason is not None:
            self._trip(reason, step, host_loss)

    def flush(self, step: int) -> None:
        """Flags-only check (no host loss) — checkpoint/epoch boundaries."""
        self.check(None, step)

    # -- trip -----------------------------------------------------------------

    def _trip(self, reason: str, step: int, host_loss: float | None) -> None:
        action = self.policy
        self.trips.inc(reason=reason, action=action)
        if self.flight is not None:
            self.flight.dump(
                f"sentinel_{reason}",
                extra={"sentinel_step": step, "sentinel_loss": host_loss,
                       "sentinel_action": action},
            )
        msg = (f"sentinel trip at step {step}: reason={reason} "
               f"action={action} loss={host_loss}")
        if action == "rollback":
            raise SentinelRollback(msg)
        if action == "abort":
            raise SentinelAbort(msg)
        self.logger.warning("%s (continuing)", msg)

    def reset_after_rollback(self) -> None:
        """Drop flags queued before the restore and restart the spike
        baseline (the restored regime's losses differ from the poisoned
        run's tail)."""
        self._pending.clear()
        self._history.clear()
        self._deferred_reason = None
